"""HTTP serving: /healthz + /metrics.

Reference: the scheduler binary starts a Prometheus handler on
--listen-address (cmd/scheduler/app/server.go:96-99) and a healthz
endpoint (pkg/apis/helpers/helpers.go:195 StartHealthz); controllers and
admission do the same.  Here one small threaded server carries both:

  GET /healthz     → 200 "ok"      (liveness)
  GET /metrics     → Prometheus text exposition of metrics.registry
  GET /trace/last  → Chrome trace_event JSON of the last completed
                     scheduling cycle (404 when tracing is disabled or
                     no cycle has finished yet) — open it in
                     chrome://tracing / Perfetto.  Forensics, so gated
                     like /debug/stacks: loopback always, non-loopback
                     only with debug_enabled
  GET /explain     → JSON "why is my job pending": unschedulable jobs,
                     their per-task fit-error messages and reason
                     histograms (serving/explain.py).  Narrow with
                     ?namespace=&job=.  Scheduler daemon only; gated
                     like /debug/stacks

No third-party client library — metrics._Registry.render() already
emits the text format.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from volcano_tpu.metrics import metrics


class _Handler(BaseHTTPRequestHandler):
    server_version = "volcano-tpu"

    def _deny_unless_debug(self) -> bool:
        """One gate for every forensics endpoint (/debug/stacks,
        /trace/last): answer an empty 404 and return True unless the
        client is loopback or debug serving is explicitly enabled."""
        if debug_allowed(
            getattr(self.server, "debug_enabled", False),
            self.client_address[0],
        ):
            return False
        self.send_response(404)
        self.send_header("Content-Length", "0")
        self.end_headers()
        return True

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path == "/healthz":
            check = getattr(self.server, "health_check", None)
            if check is not None and not check():
                body = b"unhealthy"
                self.send_response(503)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            # degraded ≠ unhealthy: the daemon is alive and scheduling,
            # but a fallback rung is carrying the load (open circuit
            # breaker, unreachable compute-plane sidecar).  200 so
            # liveness probes don't restart a working pod; the body
            # names the reason so operators and the chaos harness see
            # the demotion.
            degraded = getattr(self.server, "degraded_source", None)
            reason = degraded() if degraded is not None else None
            body = f"degraded: {reason}".encode() if reason else b"ok"
            ctype = "text/plain"
        elif self.path == "/metrics":
            body = self.server.registry.render().encode()
            ctype = "text/plain; version=0.0.4"
        elif self.path == "/trace/last":
            # scheduling forensics (task uids, node placements, evict
            # reasons) — same sensitivity class as /debug/stacks, same
            # gate: loopback always, non-loopback only with debug_enabled
            if self._deny_unless_debug():
                return
            import json

            from volcano_tpu import trace
            from volcano_tpu.trace.export import chrome_trace

            rec = getattr(self.server, "recorder", None) or trace.get_recorder()
            record = rec.last_cycle()
            if record is None:
                body = b"no recorded cycle (is tracing enabled?)"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            body = json.dumps(chrome_trace(record)).encode()
            ctype = "application/json"
        elif self.path == "/explain" or self.path.startswith("/explain?"):
            # unschedulability forensics (job/task names, node names,
            # failure reasons) — same sensitivity class and gate as
            # /debug/stacks
            if self._deny_unless_debug():
                return
            source = getattr(self.server, "explain_source", None)
            if source is None:
                body = b"no explain source (scheduler daemon only)"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            import json
            from urllib.parse import parse_qs, urlsplit

            query = parse_qs(urlsplit(self.path).query)
            data = source(
                query.get("namespace", [""])[0], query.get("job", [""])[0]
            )
            if data is None:
                body = b"job not found or nothing recorded"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            body = json.dumps(data).encode()
            ctype = "application/json"
        elif self.path == "/debug/stacks":
            # the pprof-goroutine analogue (cmd/scheduler/main.go:25
            # imports net/http/pprof): live thread stacks for hang
            # forensics.  Stack dumps leak internals (paths, job names,
            # lock state), so off-loopback binds must opt in explicitly
            # via debug_enabled — a metrics port exposed cluster-wide
            # must not also expose forensics.
            if self._deny_unless_debug():
                return
            import sys
            import threading
            import traceback

            frames = sys._current_frames()
            parts = []
            for t in threading.enumerate():
                frame = frames.get(t.ident)
                parts.append(f"--- {t.name} (daemon={t.daemon}) ---")
                if frame is not None:
                    parts.append("".join(traceback.format_stack(frame)))
            body = "\n".join(parts).encode()
            ctype = "text/plain"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass


def _default_degraded() -> Optional[str]:
    """Default /healthz degraded source: every open circuit breaker in
    the process (executor demotions, unreachable compute-plane)."""
    from volcano_tpu.faults.breaker import degraded_reasons

    reasons = degraded_reasons()
    return "; ".join(reasons) if reasons else None


def debug_allowed(debug_enabled: bool, client_ip: str) -> bool:
    """/debug/stacks policy: loopback always, anything else only with
    the explicit opt-in."""
    return debug_enabled or client_ip in ("127.0.0.1", "::1")


class ServingServer:
    """Threaded healthz+metrics server.  ``port=0`` binds an ephemeral
    port (read it back from ``.port`` after start)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        registry=None,
        health_check=None,
        debug_enabled: bool = False,
        recorder=None,
        explain_source=None,
        degraded_source=None,
    ):
        self._host = host
        self._port = port
        self._registry = registry if registry is not None else metrics.registry
        #: optional () -> bool; False turns /healthz into a 503 (liveness
        #: must reflect the daemon's loop, not just the process)
        self._health_check = health_check
        #: serve /debug/stacks to non-loopback clients (off by default)
        self._debug_enabled = debug_enabled
        #: trace recorder serving /trace/last; None = the process-global
        #: recorder at request time (trace.get_recorder())
        self._recorder = recorder
        #: optional (namespace, job) -> dict|None backing /explain —
        #: the scheduler daemon wires serving/explain.explain_jobs here
        self._explain_source = explain_source
        #: optional () -> Optional[str]; a non-empty reason turns
        #: /healthz's 200 body into "degraded: <reason>".  None = the
        #: process-global breaker registry (volcano_tpu.faults.breaker)
        self._degraded_source = (
            degraded_source
            if degraded_source is not None
            else _default_degraded
        )
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        assert self._httpd is not None, "server not started"
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._host

    def start(self) -> "ServingServer":
        self._httpd = ThreadingHTTPServer((self._host, self._port), _Handler)
        self._httpd.registry = self._registry
        self._httpd.health_check = self._health_check
        self._httpd.debug_enabled = self._debug_enabled
        self._httpd.recorder = self._recorder
        self._httpd.explain_source = self._explain_source
        self._httpd.degraded_source = self._degraded_source
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="vtpu-serving", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
