"""ConfigMap-lock leader election.

Reference: the scheduler/controllers binaries wrap their run loop in
``leaderelection.RunOrDie`` over a ConfigMap resource lock
(cmd/scheduler/app/server.go:110-156): candidates try to acquire or
renew a lease record {holderIdentity, leaseDurationSeconds, renewTime}
stored in a ConfigMap annotation; whoever wins runs the component, and
a crashed leader's lease expires so a standby takes over and rebuilds
state from watches.

The standalone equivalent stores the lease in a ConfigMap on the bus —
the in-process API server, or a remote ``vtpu-apiserver`` through
``bus.RemoteAPIServer`` (the same interface) — and uses its
resourceVersion compare-and-update (the same optimistic concurrency the
k8s lock uses) so two candidates can never both win a term.  Over the
remote bus the lease arbitrates OS *processes*: SIGKILL the active
scheduler and a standby in another process takes over after expiry.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

from volcano_tpu.apis import core
from volcano_tpu.client.apiserver import (
    AlreadyExistsError,
    ApiError,
    APIServer,
    ConflictError,
    NotFoundError,
)
from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

LEASE_KEY = "control-plane.volcano.tpu/leader"


class LeaderElector:
    """Acquire/renew loop over a ConfigMap lease.

    ``on_started_leading`` runs on the elector thread once leadership is
    acquired; ``on_stopped_leading`` fires if renewal is lost.  Use
    ``is_leader`` from component loops to gate work per cycle (the
    pattern the daemons use), or block in ``on_started_leading``.
    """

    def __init__(
        self,
        api: APIServer,
        lock_name: str,
        identity: str,
        namespace: str = "volcano-system",
        lease_duration: float = 2.0,
        retry_period: float = 0.2,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.api = api
        self.lock_name = lock_name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leader = threading.Event()
        self._stop = threading.Event()
        self._release_on_stop = True
        self._thread: Optional[threading.Thread] = None
        #: monotonic stamp of the last attempt that successfully renewed
        #: — is_leader expires against it, see the property
        self._last_renew = 0.0

    # ---- lease record ----

    def _read(self):
        cm = self.api.get("ConfigMap", self.namespace, self.lock_name)
        if cm is None:
            return None, None
        try:
            rec = json.loads(cm.data.get(LEASE_KEY, "{}"))
        except (ValueError, AttributeError):
            rec = {}
        return cm, rec

    def _write(self, cm, rec) -> bool:
        payload = {LEASE_KEY: json.dumps(rec)}
        try:
            if cm is None:
                obj = core.ConfigMap(
                    metadata=core.ObjectMeta(
                        name=self.lock_name, namespace=self.namespace
                    ),
                    data=payload,
                )
                self.api.create(obj)
            else:
                cm.data = payload
                self.api.compare_and_update(cm, cm.metadata.resource_version)
            return True
        except (AlreadyExistsError, ConflictError, NotFoundError):
            return False

    def _try_acquire_or_renew(self) -> bool:
        # Wall clock, NOT time.monotonic(): renewTime is written by one
        # candidate and compared by others, and monotonic clocks have
        # process-local epochs — a standby reading a leader's monotonic
        # timestamp judges expiry against garbage.  Wall time matches the
        # reference's leaderelection RenewTime semantics (clock-skew
        # bounded by leaseDuration, as upstream documents).
        now = time.time()
        cm, rec = self._read()
        holder = rec.get("holderIdentity") if rec else None
        renew = float(rec.get("renewTime", 0.0)) if rec else 0.0
        # Expiry is judged by the HOLDER's advertised duration (stored in
        # the record), not the reader's own config — otherwise a standby
        # configured with a shorter lease could steal a live lease.
        held_duration = (
            float(rec.get("leaseDurationSeconds", self.lease_duration))
            if rec
            else self.lease_duration
        )
        expired = now - renew > held_duration

        if cm is not None and holder not in (None, "", self.identity) and not expired:
            return False  # someone else holds a live lease
        new_rec = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": self.lease_duration,
            "renewTime": now,
        }
        return self._write(cm, new_rec)

    # ---- public API ----

    @property
    def is_leader(self) -> bool:
        """Leadership, self-expiring against the lease clock.

        The event alone is not enough over a network bus: a renew RPC
        can block for multiples of the lease duration (degraded link),
        during which a healthy standby legally acquires the expired
        lease.  Gating on lease validity here means the old leader's
        consumers (the daemon work loops check this every cycle) stop
        acting at the moment the lease lapses — not when the blocked
        RPC finally returns — so two candidates can never both act as
        leader."""
        return (
            self._leader.is_set()
            and time.monotonic() - self._last_renew <= self.lease_duration
        )

    def run(self) -> None:
        """Blocking acquire/renew loop (the RunOrDie analogue)."""
        became_leader = False
        while not self._stop.is_set():
            # stamp BEFORE the round-trip (client-go semantics): the
            # lease record's renewTime is written with the pre-call
            # clock, so judging our own validity from a post-call stamp
            # would overstate it by the RPC duration — on a congested
            # bus that is a dual-leadership window
            attempt_started = time.monotonic()
            try:
                ok = self._try_acquire_or_renew()
                if ok:
                    self._last_renew = attempt_started
            except ApiError as e:
                # A bus outage must not crash the elector thread — and a
                # single dropped request must not flap leadership: while
                # the last successful renew is younger than the lease
                # duration, the lease is still provably ours (no standby
                # can acquire it), so keep leading and retry.  Only when
                # renewal keeps failing past the lease's validity do we
                # step down (client-go leaderelection semantics).
                log.error("leader election: renew failed for %s: %s",
                          self.identity, e)
                ok = (
                    became_leader
                    and time.monotonic() - self._last_renew
                    <= self.lease_duration
                )
            if ok and not became_leader:
                became_leader = True
                self._leader.set()
                log.info("leader election: %s became leader of %s", self.identity, self.lock_name)
                if self.on_started_leading:
                    self.on_started_leading()
            elif not ok and became_leader:
                became_leader = False
                self._leader.clear()
                log.error("leader election: %s LOST %s", self.identity, self.lock_name)
                if self.on_stopped_leading:
                    self.on_stopped_leading()
            self._stop.wait(self.retry_period)
        # graceful release: zero the lease so a standby takes over fast
        if became_leader and self._release_on_stop:
            try:
                cm, rec = self._read()
                if cm is not None and rec.get("holderIdentity") == self.identity:
                    self._write(cm, {"holderIdentity": "", "renewTime": 0.0})
            except ApiError as e:
                # bus down at shutdown: the lease simply expires
                log.error("leader election: release failed for %s: %s",
                          self.identity, e)
            self._leader.clear()

    def start(self) -> "LeaderElector":
        """Run the loop on a daemon thread."""
        self._thread = threading.Thread(
            target=self.run, name=f"leader-{self.identity}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        """Stop renewing.  ``release=False`` simulates a crash: the lease
        is left to expire, exercising standby takeover."""
        self._release_on_stop = release
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if not release:
            self._leader.clear()
