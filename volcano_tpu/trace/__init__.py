"""volcano_tpu.trace — cycle record/replay journal.

Three pieces (ISSUE: the decision-level audit trail the metrics catalog
lacks):

  * **recorder** — thread-safe span/event capture per scheduling cycle
    (recorder.py), zero-cost when disabled.
  * **journal**  — JSONL event log + sampled npz PackedSnapshot captures
    in a bounded on-disk ring (journal.py).
  * **replayer** — deterministic re-execution of a captured snapshot
    through any executor, diffed against the recorded bindings
    (replay.py ``verify()``), plus Chrome trace_event timeline export
    (export.py).

Usage::

    from volcano_tpu import trace

    trace.enable("/var/log/vtpu-trace", snapshot_every=10)
    ...  # scheduler cycles record themselves
    result = trace.replay.verify("/var/log/vtpu-trace", executor="jax")
    assert result.match

Instrumented code always goes through :func:`get_recorder`; with tracing
off that returns the shared ``NullRecorder`` whose calls are no-ops.
"""

from __future__ import annotations

from typing import Optional

from volcano_tpu.trace import export, journal, replay  # noqa: F401
from volcano_tpu.trace.export import (
    chrome_trace,
    export_chrome_trace,
    export_merged_chrome_trace,
    merge_chrome_traces,
)
from volcano_tpu.trace.journal import Journal
from volcano_tpu.trace.recorder import NullRecorder, TraceRecorder
from volcano_tpu.trace.replay import ReplayResult, run_snapshot, verify

_NULL = NullRecorder()
_recorder = _NULL

#: correlation id of the scheduling cycle currently executing in this
#: process (-1 outside a cycle).  Set by the scheduler loop every
#: run_once — independent of whether a recorder is installed — and
#: attached to outbound VBUS request frames (bus/remote.py) so a
#: pending task can be followed scheduler → bus → controllers across
#: process boundaries.
_current_cycle: int = -1


def set_current_cycle(cycle_id: int) -> None:
    global _current_cycle
    _current_cycle = cycle_id


def current_cycle() -> int:
    return _current_cycle


def get_recorder():
    """The active recorder — NullRecorder unless :func:`enable` (or
    :func:`set_recorder`) installed a live one."""
    return _recorder


def set_recorder(rec: Optional[TraceRecorder]) -> None:
    global _recorder
    _recorder = rec if rec is not None else _NULL


def enable(
    journal_dir: Optional[str] = None,
    snapshot_every: int = 0,
    keep: int = 64,
) -> TraceRecorder:
    """Install a live recorder.  With ``journal_dir`` set, completed
    cycles append to the bounded on-disk ring there; ``snapshot_every=N``
    additionally captures the packed session + kernel assignment every
    Nth cycle for replay."""
    jr = Journal(journal_dir, keep=keep) if journal_dir else None
    rec = TraceRecorder(journal=jr, snapshot_every=snapshot_every)
    set_recorder(rec)
    return rec


def disable() -> None:
    set_recorder(None)


__all__ = [
    "Journal",
    "NullRecorder",
    "ReplayResult",
    "TraceRecorder",
    "chrome_trace",
    "current_cycle",
    "set_current_cycle",
    "disable",
    "enable",
    "export_chrome_trace",
    "export_merged_chrome_trace",
    "get_recorder",
    "merge_chrome_traces",
    "replay",
    "run_snapshot",
    "set_recorder",
    "verify",
]
