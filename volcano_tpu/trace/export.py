"""Chrome ``trace_event`` export — view recorded cycles in
chrome://tracing / Perfetto.

The recorder already emits Chrome-shaped events (ph "X" complete spans
with ts/dur in microseconds, ph "i" instants); this module wraps them in
the JSON object format and renders decisions as instant events on a
dedicated "decisions" track so bind/evict activity lines up with the
spans that produced it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

#: synthetic track (tid) for decision instants, kept clear of real thread ids
_DECISIONS_TID = 0


def chrome_trace(record: Dict[str, Any]) -> Dict[str, Any]:
    """One recorder cycle record → Chrome trace JSON object."""
    pid = 1
    events = []
    for e in record.get("events", []):
        ev = {
            "name": e.get("name", ""),
            "cat": e.get("cat", "event"),
            "ph": e.get("ph", "i"),
            "ts": e.get("ts", 0.0),
            "pid": pid,
            "tid": e.get("tid", 1),
        }
        if ev["ph"] == "X":
            ev["dur"] = e.get("dur", 0.0)
        if ev["ph"] == "i":
            ev["s"] = "t"  # thread-scoped instant
        if e.get("args"):
            ev["args"] = e["args"]
        events.append(ev)
    ts0 = record.get("start_us", 0.0)
    for d in record.get("decisions", []):
        events.append(
            {
                "name": f"{d.get('kind', 'bind')}:{d.get('task', '')}",
                "cat": "decision",
                "ph": "i",
                # pre-ts journals (no "ts" on decisions) fall back to
                # the cycle start
                "ts": d.get("ts", ts0),
                "pid": pid,
                "tid": _DECISIONS_TID,
                "s": "t",
                "args": {k: v for k, v in d.items() if k != "ts"},
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "cycle": record.get("cycle", -1),
            "duration_ms": record.get("duration_ms", 0.0),
            "wall_time": record.get("wall_time", 0.0),
            "n_decisions": len(record.get("decisions", [])),
            # >0 means the per-cycle cap truncated the capture: the
            # timeline below is incomplete, not a full record
            "n_dropped": record.get("n_dropped", 0),
        },
    }


def export_chrome_trace(
    journal, cycle: Optional[int] = None, path: Optional[str] = None
) -> str:
    """Render a journaled cycle to a Chrome trace JSON file; returns the
    rendered JSON string (and writes it when ``path`` is given)."""
    from volcano_tpu.trace.journal import Journal

    if isinstance(journal, str):
        journal = Journal(journal)
    if cycle is None:
        cycle = journal.last_cycle()
        if cycle is None:
            raise FileNotFoundError(f"journal {journal.root!r} has no cycles")
    text = json.dumps(chrome_trace(journal.read_cycle(cycle)), indent=1)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
