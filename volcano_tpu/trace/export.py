"""Chrome ``trace_event`` export — view recorded cycles in
chrome://tracing / Perfetto.

The recorder already emits Chrome-shaped events (ph "X" complete spans
with ts/dur in microseconds, ph "i" instants); this module wraps them in
the JSON object format and renders decisions as instant events on a
dedicated "decisions" track so bind/evict activity lines up with the
spans that produced it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

#: synthetic track (tid) for decision instants, kept clear of real thread ids
_DECISIONS_TID = 0


def _record_events(
    record: Dict[str, Any], pid: int, ts_offset_us: float = 0.0
) -> List[Dict[str, Any]]:
    """One cycle record's events+decisions as Chrome events under one
    pid row, timestamps shifted by ``ts_offset_us`` (the per-process
    clock-alignment correction the merged export computes)."""
    events = []
    for e in record.get("events", []):
        ev = {
            "name": e.get("name", ""),
            "cat": e.get("cat", "event"),
            "ph": e.get("ph", "i"),
            "ts": e.get("ts", 0.0) + ts_offset_us,
            "pid": pid,
            "tid": e.get("tid", 1),
        }
        if ev["ph"] == "X":
            ev["dur"] = e.get("dur", 0.0)
        if ev["ph"] == "i":
            ev["s"] = "t"  # thread-scoped instant
        if e.get("args"):
            ev["args"] = e["args"]
        events.append(ev)
    ts0 = record.get("start_us", 0.0)
    for d in record.get("decisions", []):
        events.append(
            {
                "name": f"{d.get('kind', 'bind')}:{d.get('task', '')}",
                "cat": "decision",
                "ph": "i",
                # pre-ts journals (no "ts" on decisions) fall back to
                # the cycle start
                "ts": d.get("ts", ts0) + ts_offset_us,
                "pid": pid,
                "tid": _DECISIONS_TID,
                "s": "t",
                "args": {k: v for k, v in d.items() if k != "ts"},
            }
        )
    return events


def chrome_trace(record: Dict[str, Any]) -> Dict[str, Any]:
    """One recorder cycle record → Chrome trace JSON object."""
    return {
        "traceEvents": _record_events(record, pid=1),
        "displayTimeUnit": "ms",
        "metadata": {
            "cycle": record.get("cycle", -1),
            "duration_ms": record.get("duration_ms", 0.0),
            "wall_time": record.get("wall_time", 0.0),
            "n_decisions": len(record.get("decisions", [])),
            # >0 means the per-cycle cap truncated the capture: the
            # timeline below is incomplete, not a full record
            "n_dropped": record.get("n_dropped", 0),
        },
    }


def _wall_start_us(record: Dict[str, Any]) -> float:
    """Wall-clock µs of the cycle's start: end-of-cycle wall stamp
    minus the measured duration.  The recorder's event timestamps are
    perf-counter µs relative to a process-local epoch — useless across
    processes — but every record also carries ``wall_time``, which
    anchors the local timeline to the shared wall clock."""
    return (
        record.get("wall_time", 0.0) * 1e6
        - record.get("duration_ms", 0.0) * 1e3
    )


def merge_chrome_traces(
    records: List[Dict[str, Any]],
    labels: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """N per-process cycle records → ONE Chrome trace with a distinct
    pid row (and process_name metadata) per record, all shifted onto
    the shared wall-clock origin, so the multiproc drills produce a
    readable combined timeline instead of N overlapping pid-1 rows.
    Cross-host clock skew shifts whole rows, never widths."""
    events: List[Dict[str, Any]] = []
    if not records:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    wall_starts = [_wall_start_us(r) for r in records]
    origin = min(w for w in wall_starts) if wall_starts else 0.0
    for i, (record, wall) in enumerate(zip(records, wall_starts)):
        pid = i + 1
        name = (labels[i] if labels and i < len(labels)
                else f"process-{i}")
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{name} (cycle {record.get('cycle', -1)})"},
        })
        offset = (wall - origin) - record.get("start_us", 0.0)
        events.extend(_record_events(record, pid=pid, ts_offset_us=offset))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "processes": len(records),
            "clock_origin_wall_us": origin,
        },
    }


def export_chrome_trace(
    journal, cycle: Optional[int] = None, path: Optional[str] = None
) -> str:
    """Render a journaled cycle to a Chrome trace JSON file; returns the
    rendered JSON string (and writes it when ``path`` is given)."""
    from volcano_tpu.trace.journal import Journal

    if isinstance(journal, str):
        journal = Journal(journal)
    if cycle is None:
        cycle = journal.last_cycle()
        if cycle is None:
            raise FileNotFoundError(f"journal {journal.root!r} has no cycles")
    text = json.dumps(chrome_trace(journal.read_cycle(cycle)), indent=1)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def export_merged_chrome_trace(
    dirs: List[str], cycle: Optional[int] = None, path: Optional[str] = None
) -> str:
    """Merge one cycle from EACH per-process journal into a single
    multi-pid Chrome trace (``vtctl trace export -d a -d b ...``).
    ``cycle=None`` takes each journal's last cycle — the common case
    after a multiproc drill, where per-process cycle ids don't align."""
    from volcano_tpu.trace.journal import Journal

    records = []
    labels = []
    for d in dirs:
        journal = Journal(d) if isinstance(d, str) else d
        c = cycle if cycle is not None else journal.last_cycle()
        if c is None:
            raise FileNotFoundError(f"journal {journal.root!r} has no cycles")
        records.append(journal.read_cycle(c))
        labels.append(str(getattr(journal, "root", d)))
    text = json.dumps(merge_chrome_traces(records, labels=labels), indent=1)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
