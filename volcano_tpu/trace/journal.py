"""On-disk cycle journal — JSONL event log + sampled npz snapshots, kept
as a bounded ring.

Layout under the journal root::

    cycle-00000012.jsonl   header line, then one JSON line per event and
                           per decision (``{"rec": "event"|"decision", ...}``)
    cycle-00000012.npz     optional PackedSnapshot + kernel assignment
                           (ops/packing.py save_snapshot format)

``keep`` bounds the ring: after each write the oldest cycles beyond it
are deleted (events and snapshot together), so a long-running scheduler
journals indefinitely in constant disk.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

_CYCLE_RE = re.compile(r"^cycle-(\d+)\.jsonl$")
_SNAP_RE = re.compile(r"^cycle-(\d+)\.npz$")


class Journal:
    def __init__(self, root: str, keep: int = 64):
        if keep < 1:
            # keep=0 would delete each cycle right after writing it —
            # never what anyone means (unlike snapshot_every, where 0
            # reads as "never capture")
            raise ValueError(f"journal keep must be >= 1, got {keep}")
        self.root = root
        self.keep = keep

    def _listdir(self) -> List[str]:
        # read-only consumers (replay/diff/export) must not create the
        # directory as a side effect; a missing (or unreadable, or
        # not-a-directory) journal just has no cycles.  Writes create it
        # (write_cycle / write_snapshot) and surface their own errors.
        try:
            return os.listdir(self.root)
        except OSError:
            return []

    # ---- paths ----

    def _events_path(self, cycle: int) -> str:
        return os.path.join(self.root, f"cycle-{cycle:08d}.jsonl")

    def _snap_path(self, cycle: int) -> str:
        return os.path.join(self.root, f"cycle-{cycle:08d}.npz")

    # ---- write ----

    def write_cycle(self, record: Dict[str, Any]) -> str:
        """Persist one assembled cycle record (recorder.end_cycle)."""
        cycle = record["cycle"]
        os.makedirs(self.root, exist_ok=True)
        path = self._events_path(cycle)
        header = {
            "rec": "cycle",
            "cycle": cycle,
            "start_us": record.get("start_us", 0.0),
            "duration_ms": record.get("duration_ms", 0.0),
            "wall_time": record.get("wall_time", 0.0),
            "n_events": len(record.get("events", [])),
            "n_decisions": len(record.get("decisions", [])),
            "snapshot": os.path.exists(self._snap_path(cycle)),
        }
        if record.get("n_dropped"):
            # a capped cycle must journal as incomplete, not complete
            header["n_dropped"] = record["n_dropped"]
        with open(path, "w") as f:
            f.write(json.dumps(header) + "\n")
            for e in record.get("events", []):
                f.write(json.dumps({"rec": "event", **e}) + "\n")
            for d in record.get("decisions", []):
                f.write(json.dumps({"rec": "decision", **d}) + "\n")
        self._prune()
        return path

    def write_snapshot(
        self, cycle: int, snap, assignment, executor: str = "",
        weights=None, gang_rounds=None,
    ) -> str:
        from volcano_tpu.ops.packing import save_snapshot

        import numpy as np

        os.makedirs(self.root, exist_ok=True)
        path = self._snap_path(cycle)
        extras = {
            "assignment": np.asarray(assignment, dtype=np.int32),
            "executor": np.array(executor),
            "cycle": np.array(cycle, dtype=np.int64),
        }
        if weights is not None:
            # ScoreWeights NamedTuple → float lanes (bool lanes included)
            extras["weights"] = np.asarray(tuple(weights), dtype=np.float64)
        if gang_rounds is not None:
            extras["gang_rounds"] = np.array(gang_rounds, dtype=np.int64)
        save_snapshot(snap, path, **extras)
        return path

    def _prune(self) -> None:
        # union of event-log and snapshot cycles, so an orphan .npz from
        # a cycle whose event log never landed still ages out of the ring
        cycles = sorted(set(self.cycles()) | set(self.snapshot_cycles()))
        for cycle in cycles[: max(0, len(cycles) - self.keep)]:
            for path in (self._events_path(cycle), self._snap_path(cycle)):
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass

    # ---- read ----

    def cycles(self) -> List[int]:
        out = []
        for name in self._listdir():
            m = _CYCLE_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def snapshot_cycles(self) -> List[int]:
        # strict match like cycles(): a foreign file (cycle-keep.npz, a
        # user-renamed backup) must be ignored, not crash every caller
        return sorted(
            int(m.group(1))
            for m in map(_SNAP_RE.match, self._listdir())
            if m
        )

    def last_cycle(self) -> Optional[int]:
        cycles = self.cycles()
        return cycles[-1] if cycles else None

    def read_cycle(self, cycle: int) -> Dict[str, Any]:
        """Inverse of write_cycle: {header, events, decisions} dict in the
        recorder's in-memory record shape."""
        path = self._events_path(cycle)
        header: Dict[str, Any] = {}
        events: List[Dict[str, Any]] = []
        decisions: List[Dict[str, str]] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                kind = obj.pop("rec", "event")
                if kind == "cycle":
                    header = obj
                elif kind == "decision":
                    decisions.append(obj)
                else:
                    events.append(obj)
        record = {
            "cycle": header.get("cycle", cycle),
            "start_us": header.get("start_us", 0.0),
            "duration_ms": header.get("duration_ms", 0.0),
            "wall_time": header.get("wall_time", 0.0),
            "events": events,
            "decisions": decisions,
        }
        if header.get("n_dropped"):
            record["n_dropped"] = header["n_dropped"]
        return record

    def read_snapshot(self, cycle: int) -> Tuple[object, Dict[str, Any]]:
        """(PackedSnapshot, extras) — extras carry ``assignment`` (int32
        array), ``executor`` (str) and ``cycle``."""
        from volcano_tpu.ops.packing import load_snapshot

        snap, extras = load_snapshot(self._snap_path(cycle))
        if "executor" in extras:
            extras["executor"] = str(extras["executor"])
        if "cycle" in extras:
            extras["cycle"] = int(extras["cycle"])
        return snap, extras
