"""Cycle trace recorder — span/event capture keyed by a monotonically
increasing cycle id.

The scheduler loop opens a cycle per ``run_once``; framework, actions and
ops emit spans (timed regions) and instant events into the recorder, and
session mutating ops emit the cycle's *decision set* (bind / pipeline /
evict / dispatch tuples).  At ``end_cycle`` the assembled record is kept
in memory (``last_cycle``) and appended to the journal when one is
attached.

Zero-cost when disabled: the module-level default is a ``NullRecorder``
whose methods are empty and whose ``enabled`` flag lets hot paths skip
argument construction entirely (``if rec.enabled: ...``).  The enabled
recorder buffers plain dicts in memory — no I/O inside the cycle except
the sampled snapshot capture — so event-granularity recording stays well
under the 5% cycle-latency budget (bench/prof_trace_overhead.py).

Timestamps are ``time.perf_counter`` microseconds relative to the
recorder's epoch, the unit Chrome's ``trace_event`` format expects
(trace/export.py).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional


class _NullSpan:
    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class NullRecorder:
    """No-op recorder — the disabled default.  Every method is empty and
    allocation-free so instrumented hot paths cost one attribute access."""

    enabled = False

    def begin_cycle(self) -> int:
        return -1

    def end_cycle(self, duration_s: float = 0.0) -> None:
        pass

    def event(self, name: str, cat: str = "event", **args) -> None:
        pass

    def span(self, name: str, cat: str = "span", **args) -> _NullSpan:
        return _NULL_SPAN

    def complete(
        self, name: str, cat: str, start_perf: float, duration_s: float, **args
    ) -> None:
        pass

    def decision(
        self, kind: str, task: str, node: str = "", reason: str = ""
    ) -> None:
        pass

    def should_capture(self) -> bool:
        return False

    def capture(
        self, snap, assignment, executor: str = "",
        weights=None, gang_rounds=None,
    ) -> None:
        pass

    def last_cycle(self) -> Optional[Dict[str, Any]]:
        return None


class _Span:
    """Context manager emitting one Chrome-style complete ("X") event."""

    __slots__ = ("_rec", "_name", "_cat", "_args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, cat: str, args):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._rec.complete(
            self._name,
            self._cat,
            self._t0,
            time.perf_counter() - self._t0,
            **(self._args or {}),
        )
        return False


class TraceRecorder:
    """Thread-safe span/event recorder with per-cycle assembly.

    ``journal`` (trace/journal.py) persists completed cycles; without one
    the recorder still serves ``last_cycle`` (the ``/trace/last``
    endpoint).  ``snapshot_every=N`` samples a PackedSnapshot + kernel
    assignment capture every Nth cycle (N=1 captures every cycle, 0
    never) — snapshot capture is the only potentially heavy step, hence
    the knob.
    """

    enabled = True

    #: per-cycle event cap — bounds memory when events are emitted by a
    #: process that never runs the scheduler loop (e.g. the compute-plane
    #: sidecar dispatching kernels per request): without begin/end_cycle
    #: the buffer would otherwise grow forever.  Excess events are
    #: dropped and counted in the cycle record's ``n_dropped``.
    max_events_per_cycle = 100_000

    def __init__(self, journal=None, snapshot_every: int = 0):
        self._lock = threading.Lock()
        self.journal = journal
        self.snapshot_every = snapshot_every
        self._epoch = time.perf_counter()
        self._cycle_id = -1  # guarded-by: self._lock
        if journal is not None:
            # resume after the journal's newest cycle: recording into a
            # non-empty directory must append, not interleave new cycles
            # with stale same-numbered ones (replay picks the newest
            # snapshot, which would otherwise be a previous run's).
            # Snapshot cycles count too — a crash between snapshot
            # capture and end_cycle leaves an orphan .npz whose id must
            # not be reused under a new run's event log.
            ids = journal.cycles() + journal.snapshot_cycles()
            if ids:
                self._cycle_id = max(ids)
        self._cycle_start_us = 0.0  # guarded-by: self._lock
        self._events: List[Dict[str, Any]] = []  # guarded-by: self._lock
        self._decisions: List[Dict[str, str]] = []  # guarded-by: self._lock
        self._dropped = 0  # guarded-by: self._lock
        self._last: Optional[Dict[str, Any]] = None

    # ---- time base ----

    def _to_us(self, perf_t: float) -> float:
        return (perf_t - self._epoch) * 1e6

    def now_us(self) -> float:
        return self._to_us(time.perf_counter())

    # ---- cycle lifecycle ----

    def begin_cycle(self) -> int:
        with self._lock:
            self._cycle_id += 1
            self._events = []
            self._decisions = []
            self._dropped = 0
            self._cycle_start_us = self.now_us()
            return self._cycle_id

    def end_cycle(self, duration_s: float = 0.0) -> None:
        with self._lock:
            record = {
                "cycle": self._cycle_id,
                "start_us": self._cycle_start_us,
                "duration_ms": duration_s * 1e3,
                "wall_time": time.time(),  # det: journal timestamp, never replayed
                "events": self._events,
                "decisions": self._decisions,
            }
            if self._dropped:
                record["n_dropped"] = self._dropped
            self._events = []
            self._decisions = []
            self._dropped = 0
        self._last = record
        if self.journal is not None:
            try:
                self.journal.write_cycle(record)
            except Exception:  # noqa: BLE001 — deliberate broad guard
                # forensics must never break scheduling: a full disk,
                # deleted journal dir, or an unserializable event arg
                # costs the record, not the cycle
                logging.getLogger(__name__).warning(
                    "trace journal write failed for cycle %d",
                    record["cycle"],
                    exc_info=True,
                )

    @property
    def cycle_id(self) -> int:
        with self._lock:
            return self._cycle_id

    # ---- emission ----

    def event(self, name: str, cat: str = "event", **args) -> None:
        e = {"name": name, "cat": cat, "ph": "i", "ts": self.now_us(),
             "tid": threading.get_ident()}
        if args:
            e["args"] = args
        self._append(e)

    def span(self, name: str, cat: str = "span", **args) -> _Span:
        return _Span(self, name, cat, args)

    def complete(
        self, name: str, cat: str, start_perf: float, duration_s: float, **args
    ) -> None:
        """Record an already-timed region: ``start_perf`` is the
        ``time.perf_counter`` value at region start.  Lets call sites
        reuse timings they already measure for metrics instead of timing
        twice."""
        e = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": self._to_us(start_perf),
            "dur": duration_s * 1e6,
            "tid": threading.get_ident(),
        }
        if args:
            e["args"] = args
        self._append(e)

    def _append(self, e: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events_per_cycle:
                self._dropped += 1
                return
            self._events.append(e)

    def decision(
        self, kind: str, task: str, node: str = "", reason: str = ""
    ) -> None:
        """kind ∈ {allocate, bind, pipeline, evict} — the audit tuple the
        replayer diffs against.  "bind" is emitted exactly once per
        actual cache bind (Session.dispatch, Statement commit,
        fast-apply batch); "allocate" is the session-level placement
        that precedes it.  ``ts`` lets the Chrome export place the
        instant next to the span that produced it."""
        d = {"kind": kind, "task": task, "node": node, "ts": self.now_us()}
        if reason:
            d["reason"] = reason
        with self._lock:
            # same bound as _append: decisions must not grow without
            # limit either when no cycle loop is draining them
            if len(self._decisions) >= self.max_events_per_cycle:
                self._dropped += 1
                return
            self._decisions.append(d)

    # ---- snapshot capture (sampled) ----

    def should_capture(self) -> bool:
        # one locked read of the cycle id — the raw double-read raced
        # begin_cycle on another thread (lock-discipline lint catch)
        cid = self.cycle_id
        return (
            self.journal is not None
            and self.snapshot_every > 0
            and cid >= 0
            and cid % self.snapshot_every == 0
        )

    def capture(
        self, snap, assignment, executor: str = "",
        weights=None, gang_rounds=None,
    ) -> None:
        """Persist the packed session + kernel assignment for the current
        cycle when the sampling knob says so.  ``weights`` /
        ``gang_rounds`` record the kernel parameters the assignment was
        computed with, so replay re-runs the exact same configuration."""
        if not self.should_capture():
            return
        cid = self.cycle_id
        try:
            self.journal.write_snapshot(
                cid, snap, assignment, executor,
                weights=weights, gang_rounds=gang_rounds,
            )
        except Exception:  # noqa: BLE001 — deliberate broad guard
            # same invariant as end_cycle: forensics must never break
            # scheduling — this runs inside the allocate action
            logging.getLogger(__name__).warning(
                "trace snapshot capture failed for cycle %d",
                cid,
                exc_info=True,
            )
            return
        self.event("snapshot-capture", "journal", executor=executor)

    def last_cycle(self) -> Optional[Dict[str, Any]]:
        return self._last
