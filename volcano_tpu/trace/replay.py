"""Deterministic re-execution of recorded cycles.

``verify()`` promotes the bench scripts' ad-hoc ``identical_bindings``
check into a first-class API: load a captured PackedSnapshot from the
journal, re-run it through a chosen executor, and diff the resulting
assignment against the recorded one.  All executors share one exact
semantics (ops/dispatch.py module docstring), so any diff is a real
regression — a kernel change that moved bindings, a nondeterministic
tie-break, or a corrupted capture.

Executors:

  * ``native``  — the C++ host baseline (volcano_tpu.native); raises
                  RuntimeError when the toolchain isn't available.
  * ``jax``     — the plain XLA scan (ops/kernels.run_packed), the
                  reference formulation.  Runs everywhere.
  * ``blocked`` — the blocked top-K formulation (ops/blocked.py).
  * ``pallas``  — the fused TPU kernel (TPU only).
  * ``auto``    — whatever ops/dispatch.select_executor picks here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

EXECUTORS = ("native", "jax", "blocked", "pallas", "auto")

#: ops/dispatch.select_executor vocabulary → replayable executor names
#: (the xla-scan and mesh-sharded formulations both replay through the
#: single-chip reference scan — identical bindings by contract)
_DISPATCH_ALIASES = {"xla-scan": "jax", "sharded": "jax"}


def replay_executor_name(dispatch_name: str) -> str:
    """Translate a dispatch-layer executor pick into the name
    ``run_snapshot`` accepts, for journaling."""
    return _DISPATCH_ALIASES.get(dispatch_name, dispatch_name)


@dataclass
class ReplayResult:
    cycle: int
    executor: str
    recorded_executor: str
    n_tasks: int
    n_placed_recorded: int
    n_placed_replayed: int
    n_diffs: int
    #: (task index, recorded node index, replayed node index) per mismatch
    diffs: List[Tuple[int, int, int]] = field(default_factory=list)

    @property
    def match(self) -> bool:
        return self.n_diffs == 0

    def summary(self) -> str:
        verdict = "IDENTICAL" if self.match else f"{self.n_diffs} DIFFS"
        return (
            f"cycle {self.cycle}: recorded[{self.recorded_executor}] vs "
            f"replayed[{self.executor}] over {self.n_tasks} tasks "
            f"({self.n_placed_recorded}/{self.n_placed_replayed} placed): "
            f"{verdict}"
        )


def run_snapshot(snap, executor: str = "jax", weights=None, gang_rounds: int = 3):
    """PackedSnapshot → assignment[T] through the named executor."""
    from volcano_tpu.ops.kernels import DEFAULT_WEIGHTS

    weights = weights or DEFAULT_WEIGHTS
    if executor == "native":
        from volcano_tpu import native

        if native.load() is None:
            raise RuntimeError("native executor unavailable (no C++ toolchain)")
        if weights != DEFAULT_WEIGHTS:
            # silently dropping the recorded weights would turn every
            # diff into a spurious "kernel regression" verdict
            raise RuntimeError(
                "native executor scores with DEFAULT_WEIGHTS only; this "
                "capture recorded non-default weights — replay it with "
                "the jax/blocked executor instead"
            )
        return native.baseline_allocate(snap, gang_rounds=gang_rounds)
    if executor == "jax":
        from volcano_tpu.ops.kernels import run_packed

        return run_packed(snap, weights=weights, gang_rounds=gang_rounds)
    if executor == "blocked":
        from volcano_tpu.ops.blocked import run_packed_blocked

        return run_packed_blocked(snap, weights=weights, gang_rounds=gang_rounds)
    if executor == "pallas":
        from volcano_tpu.ops.pallas_session import run_packed_pallas

        return run_packed_pallas(snap, weights=weights, gang_rounds=gang_rounds)
    if executor == "auto":
        from volcano_tpu.ops.dispatch import run_packed_auto

        return run_packed_auto(snap, weights=weights, gang_rounds=gang_rounds)
    raise ValueError(f"unknown executor {executor!r}; expected one of {EXECUTORS}")


def _as_journal(journal):
    from volcano_tpu.trace.journal import Journal

    if isinstance(journal, str):
        return Journal(journal)
    return journal


def replay(
    journal, cycle: Optional[int] = None, executor: str = "jax"
) -> ReplayResult:
    """Re-run a recorded cycle's snapshot and diff against its recorded
    assignment.  ``journal`` is a Journal or a directory path; ``cycle``
    defaults to the newest cycle with a snapshot."""
    journal = _as_journal(journal)
    if cycle is None:
        snaps = journal.snapshot_cycles()
        if not snaps:
            raise FileNotFoundError(
                f"no snapshot captures in journal {journal.root!r} "
                "(was the recorder's snapshot_every knob set?)"
            )
        cycle = snaps[-1]
    snap, extras = journal.read_snapshot(cycle)
    recorded = np.asarray(extras["assignment"], dtype=np.int64)
    # re-run with the kernel parameters the capture recorded (older
    # journals without them fall back to the defaults)
    weights = None
    if "weights" in extras:
        from volcano_tpu.ops.kernels import ScoreWeights

        lanes = [float(v) for v in np.asarray(extras["weights"]).ravel()]
        if len(lanes) == len(ScoreWeights._fields):
            weights = ScoreWeights(*lanes[:-1], lr_int_exact=bool(lanes[-1]))
        else:
            # a diff produced under substituted weights is NOT a kernel
            # regression — without this warning it would read as one
            import warnings

            warnings.warn(
                f"journal cycle {cycle}: recorded {len(lanes)} weight "
                f"lanes but ScoreWeights now has "
                f"{len(ScoreWeights._fields)} fields; replaying with "
                "DEFAULT_WEIGHTS — binding diffs may reflect the weight "
                "substitution, not a kernel regression",
                RuntimeWarning,
                stacklevel=2,
            )
    gang_rounds = int(extras.get("gang_rounds", 3))
    replayed = np.asarray(
        run_snapshot(
            snap, executor=executor, weights=weights, gang_rounds=gang_rounds
        ),
        dtype=np.int64,
    )

    n = snap.n_tasks
    rec_n, rep_n = recorded[:n], replayed[:n]
    mismatch = np.nonzero(rec_n != rep_n)[0]
    return ReplayResult(
        cycle=cycle,
        executor=executor,
        recorded_executor=extras.get("executor", ""),
        n_tasks=n,
        n_placed_recorded=int((rec_n >= 0).sum()),
        n_placed_replayed=int((rep_n >= 0).sum()),
        n_diffs=len(mismatch),
        diffs=[(int(i), int(rec_n[i]), int(rep_n[i])) for i in mismatch],
    )


def verify(
    journal, cycle: Optional[int] = None, executor: str = "jax"
) -> ReplayResult:
    """The first-class ``identical_bindings`` check: replay and return the
    diff result (``result.match`` is the old boolean)."""
    return replay(journal, cycle=cycle, executor=executor)
