"""Host-side utilities: priority queue, logging, assertions."""

from volcano_tpu.utils.priority_queue import PriorityQueue

__all__ = ["PriorityQueue"]
