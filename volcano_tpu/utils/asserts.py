"""Env-gated runtime assertions.

Reference: pkg/scheduler/util/assert — ``Assertf`` logs the violation
(with stack) and continues by default; setting the panic env var turns
violations fatal for tests/CI.  The env var here is
``VOLCANO_TPU_PANIC_ON_UNEXPECTED`` (the reference uses
``PANIC_ON_UNEXPECTED``).
"""

from __future__ import annotations

import os
import traceback

from volcano_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_PANIC = "VOLCANO_TPU_PANIC_ON_UNEXPECTED"


def panic_on_unexpected() -> bool:
    return os.environ.get(ENV_PANIC, "").lower() in ("1", "true", "yes")


def assertf(condition: bool, msg: str, *args) -> None:
    """Log-and-continue assertion; fatal when the panic env var is set."""
    if condition:
        return
    rendered = msg % args if args else msg
    if panic_on_unexpected():
        raise AssertionError(rendered)
    log.error("assertion failed: %s\n%s", rendered, "".join(traceback.format_stack(limit=6)))
