"""GC quiesce: thaw, collect, freeze.

Long-lived cluster state (a 50k-pod cache graph is millions of objects)
makes every gen-2 collection inside a hot region re-traverse it all;
freezing survivors into the permanent generation removes them from the
collector's working set.  Thaw first so objects frozen by a PREVIOUS
quiesce that have since died in a cycle are reclaimed — delayed by one
quiesce interval, never leaked.  Used by the scheduler loop
(--gc-quiesce-period) and by bench.py between configs.
"""

from __future__ import annotations

import gc


def gc_quiesce() -> None:
    gc.unfreeze()
    gc.collect()
    gc.freeze()
