"""V-leveled logging in the spirit of klog.

``VLOG_LEVEL`` env var (default 0) controls verbosity; metrics/latency
logging lives in volcano_tpu.scheduler.metrics.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVEL = int(os.environ.get("VLOG_LEVEL", "0"))

_logger = logging.getLogger("volcano_tpu")
if not _logger.handlers:
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter("%(asctime)s %(levelname).1s %(message)s"))
    _logger.addHandler(handler)
    _logger.setLevel(logging.INFO)


def v(level: int) -> bool:
    return _LEVEL >= level


def get_logger(name: str = "volcano_tpu") -> logging.Logger:
    """Child logger sharing the root handler/level."""
    if name == "volcano_tpu" or name.startswith("volcano_tpu."):
        return logging.getLogger(name)
    return _logger.getChild(name)


def info(msg: str, *args, level: int = 0) -> None:
    if _LEVEL >= level:
        _logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    _logger.warning(msg, *args)


def error(msg: str, *args) -> None:
    _logger.error(msg, *args)
