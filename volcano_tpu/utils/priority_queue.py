"""Heap priority queue driven by a less-function.

Reference: pkg/scheduler/util/priority_queue.go (container/heap over LessFn).
Stable for equal elements via an insertion sequence number, which also gives
deterministic pop order — a requirement for bindings-equivalence with the
device path.
"""

from __future__ import annotations

import functools
import heapq
from typing import Callable, List


class PriorityQueue:
    def __init__(self, less_fn: Callable[[object, object], bool]):
        self._less = less_fn
        self._heap: List = []
        self._seq = 0

    def push(self, item) -> None:
        heapq.heappush(self._heap, _Entry(item, self._seq, self._less))
        self._seq += 1

    def pop(self):
        if not self._heap:
            return None
        return heapq.heappop(self._heap).item

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)


@functools.total_ordering
class _Entry:
    __slots__ = ("item", "seq", "less")

    def __init__(self, item, seq: int, less):
        self.item = item
        self.seq = seq
        self.less = less

    def __lt__(self, other: "_Entry") -> bool:
        if self.less(self.item, other.item):
            return True
        if self.less(other.item, self.item):
            return False
        return self.seq < other.seq

    def __eq__(self, other) -> bool:
        return self is other
